"""Content-addressed on-disk cache of extracted feature matrices.

The reference re-reads and re-featurizes every BrainVision recording
on every run (PipelineBuilder.java:94-295 — there is no persistence
between the loader and the classifiers), and the fused device path
inherited that shape: ingest + DWT ran per query even when nothing
about the inputs had changed. This module closes that gap for the
pipeline's fused feature path: the ``(n, C*K)`` float32 feature matrix
and its ``(n,)`` float64 targets are stored once per *content key* and
re-runs load them instead of re-parsing, re-staging, and re-running
the device program.

Key scheme (all content, no paths or mtimes)::

    blake2b(
        per-recording [relative path, guessed number,
                       digest(.vhdr bytes), digest(.vmrk bytes),
                       digest(.eeg bytes)] in load order
        + channel set + epoch window (pre, post)
        + extractor id/config (family, wavelet index, epoch size,
          skip, feature size)
    )

so editing any byte of any file of the run, changing the guessed
number, the channel selection, the window, or the extractor geometry
all invalidate naturally — there is nothing to expire. The key
deliberately does NOT include the fused backend rung: every rung
produces tolerance-level-identical features by contract
(io/provider.FUSED_DEGRADATION_LADDER), so a cache hit serves whatever
backend computed the entry first and *skips the degradation ladder
entirely* — the fastest rung of all is not running one.

Storage is one ``.npz`` per key under the cache directory, written
via the checkpoint store's atomic tmp+``os.replace`` discipline
(``checkpoint.manager.atomic_write_bytes``), so a crash mid-store can
never leave a truncated entry. A corrupt or truncated entry (failed
``np.load``, missing arrays, shape mismatch) is treated as a miss —
counted, deleted best-effort, and rebuilt — never a crash.

Configuration:

- ``EEG_TPU_FEATURE_CACHE_DIR`` — cache directory (default: the
  XDG-style per-user scratch ``~/.cache/eeg-tpu/feature-cache``);
- ``EEG_TPU_NO_FEATURE_CACHE=1`` — disable globally;
- ``cache=false`` query parameter — disable for one pipeline run.

Attribution mirrors ``ops/plan_cache``: hits/misses/corrupt land in
``obs.metrics`` (``feature_cache.*``) and :func:`stats` is embedded on
every bench line as the ``feature_cache`` field.
"""

from __future__ import annotations

import hashlib
import io
import logging
import os
import threading
import time
from typing import Dict, Optional, Tuple

import numpy as np

logger = logging.getLogger(__name__)

#: sentinel from ``FeatureCache._acquire_fs_lock``: a live foreign
#: process holds the key (non-blocking callers treat it like an
#: in-process holder)
_FOREIGN_HELD = object()

#: cache directory override (explicit argument wins over it).
ENV_DIR = "EEG_TPU_FEATURE_CACHE_DIR"
#: set to "1" to disable the feature cache everywhere.
ENV_DISABLE = "EEG_TPU_NO_FEATURE_CACHE"

_FORMAT_VERSION = 1

_lock = threading.Lock()
_hits = 0
_misses = 0
_corrupt = 0
_cross_process_waits = 0

# -- single-flight rebuild guard ----------------------------------------
# Two pipeline runs (two plans under the multi-tenant executor, or two
# threads in one process) that MISS the same entry would both pay the
# full ingest+featurize rebuild and then race the atomic rename — the
# loser's identical bytes win the os.replace, but an entire rebuild
# was wasted. The guard serializes rebuilds per (directory, key): the
# first builder through proceeds; concurrent builders of the SAME key
# block until it finishes, then revalidate (lookup again) and hit the
# entry the leader stored. The in-process half is a condition
# variable; ACROSS processes (N local pipeline processes cold-starting
# the same session — the pod harness, N gateways on one box) a
# best-effort O_EXCL lock file beside the entry extends the same
# single-flight: foreign-process waiters poll until the lock clears or
# the entry lands (counted as ``feature_cache.cross_process_waits``),
# with a deadline-aware timeout fallback — a stale lock (dead holder)
# or a spent budget stops the wait and the caller proceeds lock-free,
# because the lock only ever saves redundant work; correctness was
# always the atomic rename's.
_flight_cond = threading.Condition(_lock)
_flights: set = set()

#: max seconds a cross-process waiter polls a foreign lock, and the
#: age past which a lock file is presumed abandoned (its holder died
#: without the ``finally`` that unlinks it)
ENV_LOCK_TIMEOUT = "EEG_TPU_CACHE_LOCK_TIMEOUT_S"
_DEFAULT_LOCK_TIMEOUT_S = 30.0
_LOCK_POLL_S = 0.05


def lock_timeout() -> float:
    value = os.environ.get(ENV_LOCK_TIMEOUT)
    if not value:
        return _DEFAULT_LOCK_TIMEOUT_S
    try:
        return float(value)
    except ValueError:
        logger.warning(
            "unparseable %s=%r; using the default %.0fs",
            ENV_LOCK_TIMEOUT, value, _DEFAULT_LOCK_TIMEOUT_S,
        )
        return _DEFAULT_LOCK_TIMEOUT_S


class BuildSlot:
    """The single-flight token for one entry rebuild. ``waited`` is
    True when another builder held the key while we arrived — the
    signal to revalidate before rebuilding. Release exactly once, in
    a ``finally``: a leader that died without releasing would block
    every waiter forever (the in-process half; the on-disk half
    self-heals via the stale-lock age)."""

    __slots__ = ("_token", "waited", "_released", "_lock_path")

    def __init__(self, token, waited: bool, lock_path=None):
        self._token = token
        self.waited = waited
        self._released = False
        self._lock_path = lock_path

    def release(self) -> None:
        if self._released:
            return
        self._released = True
        if self._lock_path is not None:
            # unlink only OUR lock: a build that outlived the stale
            # age may have had its lock broken and re-taken by
            # another process (whose pid is now in the file) —
            # deleting that live lock would invite a third rebuild
            try:
                with open(self._lock_path) as f:
                    owner = f.read().strip()
                if owner == str(os.getpid()):
                    os.unlink(self._lock_path)
            except OSError:
                pass
        with _flight_cond:
            _flights.discard(self._token)
            _flight_cond.notify_all()


def default_cache_dir() -> str:
    """Per-user scratch default (XDG-style), sibling of the persistent
    compile cache's default."""
    root = os.environ.get("XDG_CACHE_HOME") or os.path.join(
        os.path.expanduser("~"), ".cache"
    )
    return os.path.join(root, "eeg-tpu", "feature-cache")


def resolve_cache_dir(path: Optional[str] = None) -> Optional[str]:
    """The directory the cache should use, or None when disabled.
    Precedence: explicit ``path`` > ``EEG_TPU_FEATURE_CACHE_DIR`` >
    the per-user default; ``EEG_TPU_NO_FEATURE_CACHE=1`` wins over
    everything."""
    if os.environ.get(ENV_DISABLE) == "1":
        return None
    return path or os.environ.get(ENV_DIR) or default_cache_dir()


def stats() -> Dict[str, int]:
    """Process-wide hit/miss/corrupt counters — the bench's
    ``feature_cache`` payload field (schema-stable zeros when the
    cache never ran, like ``plan_cache.stats``)."""
    with _lock:
        return {
            "hits": _hits,
            "misses": _misses,
            "corrupt": _corrupt,
            "cross_process_waits": _cross_process_waits,
        }


def reset_stats() -> None:
    """Zero the counters (test/bench isolation)."""
    global _hits, _misses, _corrupt, _cross_process_waits
    with _lock:
        _hits = _misses = _corrupt = _cross_process_waits = 0


def _count_cross_process_wait() -> None:
    global _cross_process_waits
    with _lock:
        _cross_process_waits += 1


def _count(kind: str) -> None:
    global _hits, _misses, _corrupt
    from .. import obs
    from ..obs import events

    with _lock:
        if kind == "hit":
            _hits += 1
        elif kind == "miss":
            _misses += 1
        else:
            _corrupt += 1
    obs.metrics.count(f"feature_cache.{kind}")
    # telemetry: hit/miss/corrupt as span events, so a run report's
    # trace shows WHERE in the run the cache decided (no-op when off)
    events.event(f"feature_cache.{kind}")


def run_key(content_digests, channel_names, pre: int, post: int,
            extractor: Tuple) -> str:
    """Content key for one pipeline run's feature matrix.

    ``content_digests`` is the ordered ``(rel_path, guessed, digest)``
    list from ``OfflineDataProvider.content_digests()`` — the files
    that will actually load, in load order (cross-file balance state
    makes the feature/target rows a function of the whole ordered run,
    so per-run is the finest sound granularity). ``extractor`` is the
    static id/config tuple, e.g. ``("dwt-fused", 8, 512, 175, 16)``.
    """
    h = hashlib.blake2b(digest_size=20)
    h.update(b"eeg-tpu-feature-cache-v%d" % _FORMAT_VERSION)
    for rel_path, guessed, digest in content_digests:
        h.update(repr((rel_path, int(guessed), digest)).encode())
    h.update(repr(tuple(channel_names)).encode())
    h.update(repr((int(pre), int(post))).encode())
    h.update(repr(tuple(extractor)).encode())
    return h.hexdigest()


class FeatureCache:
    """One directory of content-addressed ``(features, targets)``
    entries. Construct via :func:`open_cache`."""

    def __init__(self, directory: str):
        self.directory = directory

    def _entry_path(self, key: str) -> str:
        return os.path.join(self.directory, f"{key}.npz")

    def lookup(self, key: str) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        """(features, targets) for ``key``, or None on a miss. Corrupt
        entries count as misses and are removed best-effort."""
        path = self._entry_path(key)
        if not os.path.exists(path):
            _count("miss")
            return None
        try:
            with np.load(path, allow_pickle=False) as data:
                features = np.asarray(data["features"])
                targets = np.asarray(data["targets"])
            if features.ndim != 2 or targets.shape != (features.shape[0],):
                raise ValueError(
                    f"inconsistent entry shapes {features.shape} / "
                    f"{targets.shape}"
                )
        except Exception as e:
            # truncated write survivor, zip damage, missing arrays:
            # the entry is dead weight — drop it and rebuild
            logger.warning(
                "feature cache entry %s is corrupt (%s: %s); treating "
                "as a miss", path, type(e).__name__, e,
            )
            _count("corrupt")
            _count("miss")
            try:
                os.unlink(path)
            except OSError:
                pass
            return None
        _count("hit")
        return features, targets

    def begin_build(self, key: str) -> BuildSlot:
        """Enter the single-flight section for ``key``'s rebuild:
        returns immediately for the first builder, blocks while
        another in-process builder holds the key. When the returned
        slot says ``waited``, the caller should revalidate with
        :meth:`lookup` before rebuilding — the leader almost certainly
        stored the entry (counted as ``feature_cache.single_flight_wait``).
        Pair with ``slot.release()`` in a ``finally``.

        The wait honours the ambient :mod:`~.deadline` scope via
        :func:`~.deadline.cond_wait`: a deadline-bearing plan queued
        behind another tenant's long rebuild fails fast with
        :class:`~.deadline.DeadlineExceededError` instead of blocking
        past its budget (the wait re-checks in short slices — the
        scheduler's deadline contract would otherwise stop at attempt
        boundaries).

        Cross-process, the same single-flight extends via a
        best-effort ``<key>.npz.lock`` O_EXCL file: a foreign
        process's rebuild makes this builder poll (counted —
        ``feature_cache.cross_process_waits``) until the lock clears
        or the entry lands, then revalidate like an in-process
        waiter. The fallback ladder keeps it strictly best-effort —
        stale lock (holder died), spent deadline budget, or the
        ``EEG_TPU_CACHE_LOCK_TIMEOUT_S`` ceiling all stop the wait
        and proceed lock-free (N redundant builds converge through
        the atomic rename exactly as before the lock existed)."""
        from .. import obs
        from . import deadline as deadline_mod

        token = (self.directory, key)
        waited = False
        with _flight_cond:
            if token in _flights:
                waited = True
                deadline_mod.cond_wait(
                    _flight_cond,
                    lambda: token not in _flights,
                    f"single-flight wait for feature cache entry {key}",
                )
            _flights.add(token)
        if waited:
            obs.metrics.count("feature_cache.single_flight_wait")
        try:
            lock_path = self._acquire_fs_lock(key, blocking=True)
        except BaseException:
            with _flight_cond:
                _flights.discard(token)
                _flight_cond.notify_all()
            raise
        return BuildSlot(token, waited, lock_path=lock_path)

    def try_begin_build(self, key: str) -> Optional[BuildSlot]:
        """Non-blocking :meth:`begin_build`: the slot, or None when
        another builder — in this process or, via a fresh foreign
        lock file, in another — holds the key. For store-only callers
        whose features are already computed — waiting would buy
        nothing (the holder is building this same content-addressed
        entry), and a deadline-bearing plan must not die queued
        behind a store it can simply skip."""
        token = (self.directory, key)
        with _flight_cond:
            if token in _flights:
                return None
            _flights.add(token)
        try:
            lock_path = self._acquire_fs_lock(key, blocking=False)
        except BaseException:
            with _flight_cond:
                _flights.discard(token)
                _flight_cond.notify_all()
            raise
        if lock_path is _FOREIGN_HELD:
            with _flight_cond:
                _flights.discard(token)
                _flight_cond.notify_all()
            return None
        return BuildSlot(token, False, lock_path=lock_path)

    def _lock_path_for(self, key: str) -> str:
        return self._entry_path(key) + ".lock"

    def _try_create_lock(self, path: str):
        """O_EXCL create: True = acquired, False = a live foreign
        holder, None = locking unavailable here (unwritable dir —
        best-effort means proceed without)."""
        try:
            os.makedirs(self.directory, exist_ok=True)
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            return False
        except OSError:
            return None
        try:
            os.write(fd, str(os.getpid()).encode())
        finally:
            os.close(fd)
        return True

    def _lock_is_stale(self, path: str) -> bool:
        try:
            age = max(0.0, time.time() - os.path.getmtime(path))
        except OSError:
            return False  # gone already — the caller re-checks
        return age > lock_timeout()

    def _acquire_fs_lock(self, key: str, blocking: bool):
        """The cross-process half of the single-flight. Returns the
        owned lock path; None to proceed lock-free (locking
        unavailable, timeout/deadline fallback, or the entry landed
        while waiting — the caller's revalidating lookup will hit);
        or ``_FOREIGN_HELD`` (non-blocking callers only — a live
        foreign builder holds the key)."""
        from .. import obs
        from . import deadline as deadline_mod

        path = self._lock_path_for(key)
        created = self._try_create_lock(path)
        if created is True:
            return path
        if created is None:
            return None
        if not blocking:
            if self._lock_is_stale(path):
                # break the dead holder's lock so the NEXT builder is
                # not fooled too, then take it if we win the race
                try:
                    os.unlink(path)
                except OSError:
                    pass
                return path if self._try_create_lock(path) is True else None
            return _FOREIGN_HELD
        obs.metrics.count("feature_cache.cross_process_waits")
        _count_cross_process_wait()
        wait_deadline = time.time() + lock_timeout()
        while True:
            if os.path.exists(self._entry_path(key)):
                # the foreign builder stored the entry: stop waiting —
                # the caller's revalidating lookup hits it
                return None
            if self._lock_is_stale(path):
                try:
                    os.unlink(path)
                except OSError:
                    pass
            created = self._try_create_lock(path)
            if created is True:
                return path
            if created is None:
                return None
            ambient = deadline_mod.active_deadline()
            if ambient is not None and ambient.expired:
                # deadline-aware fallback: a budget-bearing plan must
                # not die polling a lock that only saves redundant
                # work — proceed lock-free; its own work (or the
                # scope's next check) spends the budget honestly
                return None
            if time.time() >= wait_deadline:
                logger.warning(
                    "feature cache lock %s still held after %.0fs; "
                    "proceeding without it", path, lock_timeout(),
                )
                return None
            time.sleep(_LOCK_POLL_S)

    def store(self, key: str, features: np.ndarray,
              targets: np.ndarray) -> Optional[str]:
        """Atomically persist an entry; returns its path, or None when
        the directory is unwritable (a broken scratch dir must never
        kill the run that just computed the features)."""
        from ..checkpoint.manager import atomic_write_bytes
        from .. import obs

        buf = io.BytesIO()
        np.savez(
            buf,
            features=np.asarray(features),
            targets=np.asarray(targets),
        )
        path = self._entry_path(key)
        try:
            os.makedirs(self.directory, exist_ok=True)
            atomic_write_bytes(path, buf.getvalue())
        except OSError as e:
            logger.warning(
                "feature cache store failed for %s (%s); continuing "
                "uncached", path, e,
            )
            return None
        obs.metrics.count("feature_cache.store")
        return path


def open_cache(path: Optional[str] = None) -> Optional[FeatureCache]:
    """The cache for the resolved directory, or None when disabled."""
    d = resolve_cache_dir(path)
    if d is None:
        return None
    return FeatureCache(d)
