"""Pluggable filesystem layer + info.txt parsing.

Replaces the reference's hard-coded HDFS endpoint
(``Const.java:38-39``, ``OffLineDataProvider.java:90``) with a small
filesystem protocol: local POSIX by default, extensible to object
stores. The ``info.txt`` format and its quirks are preserved from
``OffLineDataProvider.loadFilesFromInfoTxt``
(OffLineDataProvider.java:283-319):

- blank lines and lines starting with ``#`` are skipped,
- each line is ``<path-to-.eeg> <guessed number> [ignored extras]``,
- single-field lines are silently ignored,
- a bad number raises,
- duplicate paths collapse, last guess wins, first-seen order kept
  (the reference stores into a ``LinkedHashMap`` —
  OffLineDataProvider.java:53).
"""

from __future__ import annotations

import os
from typing import Dict, Protocol


class FileSystem(Protocol):
    def exists(self, path: str) -> bool: ...

    def read_bytes(self, path: str) -> bytes: ...

    def read_text(self, path: str) -> str: ...

    def write_bytes(self, path: str, data: bytes) -> None: ...


class LocalFileSystem:
    """POSIX filesystem (``file://`` URIs tolerated, like the
    reference's path handling)."""

    @staticmethod
    def _strip(path: str) -> str:
        return path[len("file://") :] if path.startswith("file://") else path

    def exists(self, path: str) -> bool:
        return os.path.exists(self._strip(path))

    def size(self, path: str) -> int:
        """Byte length by stat — the pod metadata pass sizes every
        recording's .eeg without reading it (parallel/pod.py); the
        protocol method is optional (``pod.file_size`` falls back to
        ``len(read_bytes())`` for filesystems without it)."""
        return os.path.getsize(self._strip(path))

    def read_bytes(self, path: str) -> bytes:
        with open(self._strip(path), "rb") as f:
            return f.read()

    def read_text(self, path: str) -> str:
        with open(
            self._strip(path), "r", encoding="utf-8", errors="replace"
        ) as f:
            return f.read()

    def write_bytes(self, path: str, data: bytes) -> None:
        with open(self._strip(path), "wb") as f:
            f.write(data)


class InMemoryFileSystem:
    """Dict-backed filesystem for hermetic tests."""

    def __init__(self, files: Dict[str, bytes] | None = None):
        self.files: Dict[str, bytes] = dict(files or {})

    def exists(self, path: str) -> bool:
        return path in self.files

    def size(self, path: str) -> int:
        return len(self.files[path])

    def read_bytes(self, path: str) -> bytes:
        return self.files[path]

    def read_text(self, path: str) -> str:
        return self.files[path].decode("utf-8", errors="replace")

    def write_bytes(self, path: str, data: bytes) -> None:
        self.files[path] = data


def parse_info_txt(text: str) -> Dict[str, int]:
    """``info.txt`` -> ordered {relative .eeg path: guessed number}."""
    files: Dict[str, int] = {}
    for line in text.splitlines():
        if len(line) == 0:
            continue
        if line[0] == "#":
            continue
        # Java's String.split(" ") discards trailing empty strings, so
        # 'path ' (trailing space) parses as a single-field line and is
        # silently skipped (OffLineDataProvider.java:302-305).
        parts = line.split(" ")
        while parts and parts[-1] == "":
            parts.pop()
        if len(parts) > 1:
            try:
                num = int(parts[1])
            except ValueError as e:
                raise ValueError(
                    f"Line {line!r} contains an improper number format"
                ) from e
            files[parts[0]] = num
    return files
