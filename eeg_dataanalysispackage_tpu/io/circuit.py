"""Shared circuit breaker for remote endpoints.

The retry machinery in :mod:`io.remote` bounds the cost of ONE request
against a flaky endpoint (``max_attempts * timeout + backoff``), but a
*dead* endpoint still charges that full budget to every call — a
pipeline touching hundreds of objects over a downed WebHDFS gateway
stalls for minutes doing nothing but backing off. The reference has no
answer at all (its Hadoop client blocks until the RPC layer gives up,
per call, forever).

This module is the classic three-state breaker, shared process-wide
per endpoint authority so every filesystem instance dialing the same
gateway pools its failure evidence:

- **closed** — requests flow; each exhausted retry budget increments a
  consecutive-failure count (any completed request resets it);
- **open** — after ``threshold`` consecutive exhausted budgets, calls
  fail fast with :class:`CircuitOpenError` carrying the aggregated
  evidence (when it opened, how many failures, the recent errors) —
  no more per-call full-backoff stalls;
- **half-open** — after ``cooldown_s`` one probe call is let through;
  success closes the circuit, failure re-opens it (and restarts the
  cooldown clock).

State transitions are counted in ``obs.metrics``
(``circuit.opened`` / ``circuit.closed`` / ``circuit.fast_fail`` /
``circuit.probe``). ``EEG_TPU_CIRCUIT_THRESHOLD=0`` disables breaking
entirely (every call behaves as closed).
"""

from __future__ import annotations

import collections
import logging
import os
import threading
import time
from typing import Deque, Dict, Optional, Tuple

logger = logging.getLogger(__name__)

#: consecutive exhausted retry budgets before the circuit opens;
#: 0 disables the breaker
DEFAULT_THRESHOLD = 3
#: seconds the circuit stays open before a half-open probe is allowed
DEFAULT_COOLDOWN_S = 15.0
#: recent failure messages kept as evidence
_EVIDENCE_KEEP = 5

CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"


class CircuitOpenError(IOError):
    """Fail-fast refusal: the endpoint's circuit is open.

    Subclasses ``IOError`` (like ``RemoteIOError``) so callers that
    already treat remote failures as I/O errors handle it unchanged —
    the message carries the aggregated evidence instead of one more
    timed-out attempt.
    """


def _metrics():
    from .. import obs

    return obs.metrics


def _event(name: str, **attrs) -> None:
    """Telemetry span event for a breaker transition (no-op when no
    run telemetry is active — obs/events.py)."""
    from ..obs import events

    events.event(name, **attrs)


class CircuitBreaker:
    """Per-endpoint breaker; thread-safe. ``clock`` is injectable so
    tests drive the cooldown without sleeping."""

    def __init__(
        self,
        endpoint: str,
        threshold: int = DEFAULT_THRESHOLD,
        cooldown_s: float = DEFAULT_COOLDOWN_S,
        clock=time.monotonic,
    ):
        self.endpoint = endpoint
        self.threshold = threshold
        self.cooldown_s = cooldown_s
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive_failures = 0
        self._total_failures = 0
        self._opened_at: Optional[float] = None
        self._probe_in_flight = False
        self._evidence: Deque[str] = collections.deque(maxlen=_EVIDENCE_KEEP)

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    # -- call protocol -------------------------------------------------

    def allow(self) -> None:
        """Gate one call: raises :class:`CircuitOpenError` when open
        (and not yet due for a probe); otherwise lets the call proceed
        (possibly as the half-open probe)."""
        if self.threshold <= 0:
            return
        with self._lock:
            if self._state == CLOSED:
                return
            now = self._clock()
            if (
                self._state == OPEN
                and self._opened_at is not None
                and now - self._opened_at >= self.cooldown_s
            ):
                self._state = HALF_OPEN
            if self._state == HALF_OPEN and not self._probe_in_flight:
                # exactly one caller probes; the rest keep failing fast
                self._probe_in_flight = True
                _metrics().count("circuit.probe")
                logger.warning(
                    "circuit.transition endpoint=%s open->half_open "
                    "probe=allowed consecutive_failures=%d evidence=%s",
                    self.endpoint,
                    self._consecutive_failures,
                    list(self._evidence),
                )
                _event(
                    "circuit.half_open",
                    endpoint=self.endpoint,
                    consecutive_failures=self._consecutive_failures,
                )
                return
            # counter only — no ring event per fast-fail: an open-
            # circuit storm would flood the 512-slot flight recorder
            # and evict the one circuit.opened event (with evidence)
            # a crash report actually needs
            _metrics().count("circuit.fast_fail")
            raise CircuitOpenError(
                f"circuit open for {self.endpoint}: "
                f"{self._total_failures} exhausted retry budgets "
                f"({self._consecutive_failures} consecutive), open for "
                f"{0.0 if self._opened_at is None else now - self._opened_at:.1f}s; "
                f"recent errors: {list(self._evidence)}"
            )

    def record_success(self) -> None:
        """A request completed (any response counts — the endpoint is
        alive); closes a half-open circuit."""
        if self.threshold <= 0:
            return
        with self._lock:
            was = self._state
            self._state = CLOSED
            self._consecutive_failures = 0
            self._probe_in_flight = False
            self._opened_at = None
            if was != CLOSED:
                _metrics().count("circuit.closed")
                logger.warning(
                    "circuit.transition endpoint=%s %s->closed "
                    "(successful probe) prior_failures=%d evidence=%s",
                    self.endpoint,
                    was,
                    self._total_failures,
                    list(self._evidence),
                )
                _event(
                    "circuit.closed",
                    endpoint=self.endpoint,
                    prior_failures=self._total_failures,
                )

    def snapshot(self) -> Dict[str, object]:
        """The breaker's state as report evidence: state, failure
        counts, the recent (plan-tagged) evidence strings, and the
        contributing plan ids — what a run/crash report embeds so a
        tenant fast-failed by a breaker some OTHER plan opened can see
        whose requests opened it (docs/resilience.md)."""
        with self._lock:
            contributors = sorted({
                e.split("]", 1)[0][6:]
                for e in self._evidence
                if e.startswith("[plan ")
            })
            return {
                "state": self._state,
                "consecutive_failures": self._consecutive_failures,
                "total_failures": self._total_failures,
                "evidence": list(self._evidence),
                "contributing_plans": contributors,
            }

    def record_failure(self, error: Exception) -> None:
        """One exhausted retry budget against the endpoint.

        Breakers are process-global per endpoint authority BY DESIGN:
        under the multi-tenant executor, plan B fast-fails on an
        endpoint plan A's exhausted budgets opened — shared failure
        evidence is the intended cross-tenant protection (one dead
        gateway must not charge every tenant the full backoff ladder).
        Each evidence entry is therefore tagged with the plan that
        contributed it, so both plans' reports can name the opener.
        """
        if self.threshold <= 0:
            return
        from ..obs import domain as run_domain

        plan_id = run_domain.current_plan_id()
        tag = "" if plan_id is None else f"[plan {plan_id}] "
        with self._lock:
            self._consecutive_failures += 1
            self._total_failures += 1
            self._evidence.append(f"{tag}{type(error).__name__}: {error}")
            half_open_probe_failed = self._state == HALF_OPEN
            self._probe_in_flight = False
            if (
                self._consecutive_failures >= self.threshold
                or half_open_probe_failed
            ):
                if self._state != OPEN:
                    _metrics().count("circuit.opened")
                    logger.error(
                        "circuit.transition endpoint=%s %s->open "
                        "consecutive_failures=%d cooldown_s=%.0f "
                        "evidence=%s",
                        self.endpoint,
                        self._state,
                        self._consecutive_failures,
                        self.cooldown_s,
                        list(self._evidence),
                    )
                    _event(
                        "circuit.opened",
                        endpoint=self.endpoint,
                        consecutive_failures=self._consecutive_failures,
                        evidence=list(self._evidence),
                    )
                self._state = OPEN
                self._opened_at = self._clock()


# -- process-wide registry ---------------------------------------------

_REGISTRY: Dict[str, CircuitBreaker] = {}
_REGISTRY_LOCK = threading.Lock()


def _env_config() -> Tuple[int, float]:
    try:
        threshold = int(
            os.environ.get("EEG_TPU_CIRCUIT_THRESHOLD", DEFAULT_THRESHOLD)
        )
    except ValueError:
        threshold = DEFAULT_THRESHOLD
    try:
        cooldown = float(
            os.environ.get("EEG_TPU_CIRCUIT_COOLDOWN", DEFAULT_COOLDOWN_S)
        )
    except ValueError:
        cooldown = DEFAULT_COOLDOWN_S
    return threshold, cooldown


def breaker_for(endpoint: str) -> CircuitBreaker:
    """The process-shared breaker for an endpoint authority (e.g.
    ``http://nn.example:9870``) — every filesystem instance dialing the
    same authority shares one failure history."""
    with _REGISTRY_LOCK:
        breaker = _REGISTRY.get(endpoint)
        if breaker is None:
            threshold, cooldown = _env_config()
            breaker = CircuitBreaker(
                endpoint, threshold=threshold, cooldown_s=cooldown
            )
            _REGISTRY[endpoint] = breaker
        return breaker


def snapshot() -> Dict[str, Dict[str, object]]:
    """Every registered breaker's :meth:`CircuitBreaker.snapshot`,
    keyed by endpoint — the ``circuit`` block obs/report.py embeds in
    run and crash reports ({} when no remote endpoint was ever
    dialed, schema-stable)."""
    with _REGISTRY_LOCK:
        breakers = dict(_REGISTRY)
    return {
        endpoint: breaker.snapshot()
        for endpoint, breaker in breakers.items()
    }


def reset() -> None:
    """Drop all shared breakers (tests; operator 'clear the fuse')."""
    with _REGISTRY_LOCK:
        _REGISTRY.clear()
