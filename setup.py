"""Build hook: compile the native host kernels into the wheel.

The reference's build produces one deployable artifact via
maven-assembly (pom.xml:20-45); ours is a wheel that carries
``libeeg_host.so`` (int16 demux / epoch gather / balance scan,
``native/eeg_host.cc``) inside ``eeg_dataanalysispackage_tpu/io`` so
installed copies get the native fast path without a toolchain at
runtime. If g++ is unavailable the build still succeeds — every native
entry point has a bit-identical numpy fallback (io/native.py) — but
the wheel then ships without the library rather than with a stale one.
"""

import os
import shutil
import subprocess

from setuptools import setup
from setuptools.command.build_py import build_py
from setuptools.dist import Distribution

ROOT = os.path.dirname(os.path.abspath(__file__))


class BuildWithNative(build_py):
    def run(self):
        super().run()
        # never ship a library that predates the current sources: drop
        # any copy that a previous build staged, then rebuild fresh
        dest_dir = os.path.join(self.build_lib, "eeg_dataanalysispackage_tpu", "io")
        dest = os.path.join(dest_dir, "libeeg_host.so")
        if os.path.exists(dest):
            os.remove(dest)
        native_dir = os.path.join(ROOT, "native")
        try:
            subprocess.run(["make", "-B", "-C", native_dir], check=True)
            os.makedirs(dest_dir, exist_ok=True)
            shutil.copy2(os.path.join(native_dir, "libeeg_host.so"), dest)
        except (OSError, subprocess.CalledProcessError) as e:
            print(f"native build skipped ({e}); numpy fallbacks remain active")


class NativeDistribution(Distribution):
    def has_ext_modules(self):
        # the packaged .so is platform-specific: tag the wheel as such
        return True


setup(cmdclass={"build_py": BuildWithNative}, distclass=NativeDistribution)
